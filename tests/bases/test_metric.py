"""Single-process Metric lifecycle tests.

Parity in spirit with /root/reference/tests/bases/test_metric.py (383 LoC):
add_state validation, reset/cache semantics, forward double-update, compute
caching, pickle, hashing, state_dict, pure-state API.
"""
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Metric
from metrics_tpu.utils.exceptions import MetricsUserError


class DummyMetric(Metric):
    name = "Dummy"

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("x", jnp.array(0.0), dist_reduce_fx="sum")

    def _update(self, x=None):
        if x is not None:
            self.x = self.x + jnp.asarray(x, dtype=jnp.float32)

    def _compute(self):
        return self.x


class DummyListMetric(Metric):
    name = "DummyList"

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("x", [], dist_reduce_fx="cat")

    def _update(self, x=None):
        if x is not None:
            self.x.append(jnp.asarray(x, dtype=jnp.float32))

    def _compute(self):
        return self.x


def test_add_state_validation():
    m = DummyMetric()
    with pytest.raises(ValueError):
        m.add_state("bad", [jnp.array(1.0)], dist_reduce_fx="sum")
    with pytest.raises(ValueError):
        m.add_state("bad2", jnp.array(0.0), dist_reduce_fx="not_a_reduction")
    with pytest.raises(ValueError):
        m.add_state("bad3", jnp.array(0.0), dist_reduce_fx=42)  # non-callable non-string
    with pytest.raises(ValueError):
        m.add_state("bad4", object(), dist_reduce_fx="sum")  # non-arrayable default
    m.add_state("ok", jnp.zeros(3), dist_reduce_fx="mean")
    assert "ok" in m._defaults


def test_add_state_registers_working_reducers():
    """The registered string reducers actually reduce (reference
    test_metric.py:63-92), and a custom callable is kept as-is."""
    m = DummyMetric()
    m.add_state("a", jnp.array(0), dist_reduce_fx="sum")
    assert float(m._reductions["a"](jnp.asarray([1, 1]))) == 2
    m.add_state("b", jnp.array(0.0), dist_reduce_fx="mean")
    assert float(m._reductions["b"](jnp.asarray([1.0, 2.0]))) == pytest.approx(1.5)
    m.add_state("c", jnp.array(0), dist_reduce_fx="cat")
    assert m._reductions["c"]([jnp.asarray([1]), jnp.asarray([1])]).shape == (2,)
    m.add_state("mx", jnp.array(0), dist_reduce_fx="max")
    assert float(m._reductions["mx"](jnp.asarray([1, 7, 3]))) == 7
    m.add_state("mn", jnp.array(0), dist_reduce_fx="min")
    assert float(m._reductions["mn"](jnp.asarray([4, 2, 9]))) == 2

    def custom_fx(_):
        return -1

    m.add_state("e", jnp.array(0), dist_reduce_fx=custom_fx)
    assert m._reductions["e"](jnp.asarray([1, 1])) == -1


def test_warning_on_compute_before_update():
    """compute() before any update warns but still returns the
    default-state value (reference test_metric.py:301-321)."""
    m = DummyMetric()
    with pytest.warns(UserWarning, match="before the ``update`` method"):
        assert float(m.compute()) == 0.0
    # after an update, no warning
    m.update(2.0)
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        assert float(m.compute()) == 2.0


def test_update_and_compute():
    m = DummyMetric()
    m.update(1.0)
    m.update(2.0)
    assert np.allclose(m.compute(), 3.0)


def test_compute_cached_until_update():
    m = DummyMetric()
    m.update(1.0)
    assert np.allclose(m.compute(), 1.0)
    # cached
    m._computed_probe = m._computed
    assert m._computed_probe is not None
    m.update(1.0)
    assert m._computed is None
    assert np.allclose(m.compute(), 2.0)


def test_forward_returns_batch_value_and_accumulates():
    m = DummyMetric()
    b1 = m(1.0)
    assert np.allclose(b1, 1.0)
    b2 = m(2.0)
    assert np.allclose(b2, 2.0)  # batch value, not accumulation
    assert np.allclose(m.compute(), 3.0)  # global accumulation


def test_reset():
    m = DummyMetric()
    m.update(5.0)
    m.reset()
    assert np.allclose(m.x, 0.0)
    lm = DummyListMetric()
    lm.update(jnp.ones(3))
    lm.reset()
    assert lm.x == []


def test_reset_compute():
    m = DummyMetric()
    m.update(5.0)
    assert np.allclose(m.compute(), 5.0)
    m.reset()
    m.update(2.0)
    assert np.allclose(m.compute(), 2.0)


def test_list_state_append_and_compute():
    m = DummyListMetric()
    m.update(jnp.array([1.0, 2.0]))
    m.update(jnp.array([3.0]))
    out = m.compute()
    assert len(out) == 2


def test_pickle_roundtrip():
    m = DummyMetric()
    m.update(3.0)
    m2 = pickle.loads(pickle.dumps(m))
    assert np.allclose(m2.compute(), 3.0)


def test_hash_differs_between_instances():
    a, b = DummyMetric(), DummyMetric()
    assert hash(a) != hash(b)


def test_const_attr_immutable():
    m = DummyMetric()
    with pytest.raises(RuntimeError):
        m.higher_is_better = True
    with pytest.raises(RuntimeError):
        m.is_differentiable = True


def test_state_dict_roundtrip():
    m = DummyMetric()
    m.update(4.0)
    sd = m.state_dict()
    assert np.allclose(sd["x"], 4.0)
    m2 = DummyMetric()
    m2.load_state_dict(sd)
    m2._update_called = True
    assert np.allclose(m2.compute(), 4.0)


def test_state_dict_list_state():
    m = DummyListMetric()
    m.update(jnp.array([1.0, 2.0]))
    sd = m.state_dict()
    m2 = DummyListMetric()
    m2.load_state_dict(sd)
    m2._update_called = True
    out = m2.compute()
    assert np.allclose(out[0], [1.0, 2.0])


def test_pure_state_api():
    m = DummyMetric()
    s = m.init_state()
    s = m.update_state(s, 1.0)
    s = m.update_state(s, 2.0)
    assert np.allclose(m.compute_state(s), 3.0)
    # metric instance untouched
    assert np.allclose(m.x, 0.0)


def test_pure_state_api_jit():
    m = DummyMetric()
    s = m.init_state()
    step = jax.jit(m.update_state)
    s = step(s, jnp.array(1.0))
    s = step(s, jnp.array(2.0))
    assert np.allclose(m.compute_state(s), 3.0)


def test_merge_states():
    m = DummyMetric()
    a = m.update_state(m.init_state(), 1.0)
    b = m.update_state(m.init_state(), 2.0)
    merged = m.merge_states(a, b)
    assert np.allclose(m.compute_state(merged), 3.0)


def test_sync_without_distributed_is_noop():
    m = DummyMetric()
    m.update(1.0)
    m.sync()
    assert not m._is_synced
    with pytest.raises(MetricsUserError):
        m.unsync()


def test_double_sync_raises():
    m = DummyMetric()
    m.update(1.0)
    fake_gather = lambda x, group=None: [x, x]
    m.sync(dist_sync_fn=fake_gather, distributed_available=lambda: True)
    assert m._is_synced
    assert np.allclose(m.x, 2.0)  # summed over fake world of 2
    with pytest.raises(MetricsUserError):
        m.sync(dist_sync_fn=fake_gather, distributed_available=lambda: True)
    m.unsync()
    assert np.allclose(m.x, 1.0)


def test_forward_while_synced_raises():
    m = DummyMetric()
    m.update(1.0)
    m.sync(dist_sync_fn=lambda x, group=None: [x, x], distributed_available=lambda: True)
    with pytest.raises(MetricsUserError):
        m(2.0)


def test_set_dtype():
    m = DummyMetric()
    m.update(1.0)
    m.set_dtype(jnp.bfloat16)
    assert m.x.dtype == jnp.bfloat16


def test_clone_independent():
    m = DummyMetric()
    m.update(1.0)
    c = m.clone()
    c.update(1.0)
    assert np.allclose(m.compute(), 1.0)
    assert np.allclose(c.compute(), 2.0)


def test_shard_states_over_mesh():
    """SURVEY §5 long-context analog: per-class state sharded over the mesh
    stays sharded through update/compute/reset and computes correctly."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from metrics_tpu import ConfusionMatrix

    mesh = Mesh(np.array(jax.devices()[:8]), ("rank",))
    metric = ConfusionMatrix(num_classes=16)
    metric.shard_states(NamedSharding(mesh, P("rank", None)))

    rng = np.random.default_rng(0)
    preds = rng.integers(0, 16, 200)
    target = rng.integers(0, 16, 200)
    metric.update(jnp.asarray(preds), jnp.asarray(target))
    assert metric.confmat.sharding.spec == P("rank", None)

    got = np.asarray(metric.compute())
    want = np.zeros((16, 16))
    for p, t in zip(preds, target):
        want[t, p] += 1
    np.testing.assert_array_equal(got, want)

    metric.reset()
    assert metric.confmat.sharding.spec == P("rank", None)
    metric.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_array_equal(np.asarray(metric.compute()), want)


def test_enable_profiling_annotations_run():
    """Opt-in jax.profiler annotations must not change behavior."""
    m = DummyMetric()
    m.enable_profiling = True
    m.update(3.0)
    assert float(m.compute()) == 3.0


def test_shard_states_recurses_into_children():
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from metrics_tpu import ConfusionMatrix

    mesh = Mesh(np.array(jax.devices()[:8]), ("rank",))
    composed = ConfusionMatrix(num_classes=16) + ConfusionMatrix(num_classes=16)
    composed.shard_states(NamedSharding(mesh, P("rank", None)))
    assert composed.metric_a.confmat.sharding.spec == P("rank", None)
    assert composed.metric_b.confmat.sharding.spec == P("rank", None)


def test_merge_states_weighted_mean():
    """Mean-reduced states merge as a count-weighted average when counts are
    given (core/metric.py merge_states); unweighted (a+b)/2 otherwise."""

    class MeanStateMetric(Metric):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.add_state("m", jnp.array(0.0), dist_reduce_fx="mean")

        def _update(self, x):
            self.m = jnp.asarray(x, dtype=jnp.float32)

        def _compute(self):
            return self.m

    m = MeanStateMetric()
    # hand-built states without the auto counter: unweighted fallback
    a, b = {"m": jnp.array(1.0)}, {"m": jnp.array(4.0)}
    assert float(m.merge_states(a, b)["m"]) == pytest.approx(2.5)
    # side a saw 3 batches, side b saw 1: weighted mean, not midpoint
    assert float(m.merge_states(a, b, counts=(3, 1))["m"]) == pytest.approx(1.75)
    with pytest.raises(ValueError, match="pair"):
        m.merge_states(a, b, counts=(1, 2, 3))

    # full-lifecycle states carry the auto-registered update counter, so
    # uneven accumulations weight themselves without explicit counts
    sa = m.init_state()
    assert "_n_updates" in sa
    for x in (1.0, 1.0, 1.0):
        sa = m.update_state(sa, x)  # overwrite-style update; 3 updates
    sb = m.update_state(m.init_state(), 4.0)  # 1 update
    merged = m.merge_states(sa, sb)
    assert float(merged["m"]) == pytest.approx(1.75)
    assert int(merged["_n_updates"]) == 4  # counter itself sum-merges
    # explicit counts still win over the auto counter
    assert float(m.merge_states(sa, sb, counts=(1, 1))["m"]) == pytest.approx(2.5)
    # two never-updated states merge to the default, not 0/0
    fresh = m.merge_states(m.init_state(), m.init_state())
    assert float(fresh["m"]) == pytest.approx(0.0)
    # the counter increments under jit too
    sj = jax.jit(m.update_state)(m.init_state(), 2.0)
    assert int(sj["_n_updates"]) == 1

    # a pre-counter state (old checkpoint / hand-built dict) passed through
    # update_state stays counter-less — it must NOT acquire a fresh counter
    # that missed its accumulation history, so merges keep the documented
    # unweighted fallback instead of confidently wrong weights
    legacy = {"m": jnp.array(10.0)}
    legacy2 = m.update_state(legacy, 10.0)
    assert "_n_updates" not in legacy2
    assert float(m.merge_states(legacy2, {"m": jnp.array(0.0)})["m"]) == pytest.approx(5.0)


def test_custom_cat_like_reducer_flag():
    """A custom reducer marked ``cat_like=True`` gets concat semantics in
    merge_states and the pre-cat optimization in _sync_dist (the contract is
    the explicit flag, not function identity with dim_zero_cat)."""

    def my_cat(x):
        return jnp.concatenate(x) if isinstance(x, list) else x

    my_cat.cat_like = True

    class CustomCatMetric(Metric):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.add_state("vals", [], dist_reduce_fx=my_cat)

        def _update(self, x):
            self.vals.append(jnp.asarray(x, dtype=jnp.float32).reshape(-1))

        def _compute(self):
            return jnp.concatenate(self.vals) if isinstance(self.vals, list) else self.vals

    seen = []

    def spy_gather(x, group=None):
        seen.append(x if isinstance(x, list) else [x])
        return x if isinstance(x, list) else [x]

    m = CustomCatMetric(dist_sync_fn=spy_gather)
    assert m._cat_states["vals"] is True

    # merge_states concatenates instead of raising "custom reduction"
    a = {"vals": [jnp.array([1.0])]}
    b = {"vals": [jnp.array([2.0])]}
    assert len(m.merge_states(a, b)["vals"]) == 2

    # sync: the two appended arrays are pre-concatenated into ONE gather call
    m.update([1.0, 2.0])
    m.update([3.0])
    m.sync()
    assert len(seen) == 1, "pre-cat optimization must collapse the list state to a single gather"
    np.testing.assert_allclose(np.asarray(m._compute()), [1.0, 2.0, 3.0])
    m.unsync()


def test_error_on_wrong_constructor_input():
    """Constructor-kwarg validation parity (reference test_metric.py:31-37)."""
    with pytest.raises(ValueError, match="`dist_sync_on_step` to be an `bool`"):
        DummyMetric(dist_sync_on_step=None)
    with pytest.raises(ValueError, match="`dist_sync_fn` to be an callable"):
        DummyMetric(dist_sync_fn=[2, 3])


def test_error_on_not_implemented_methods():
    """A subclass must implement _update and _compute; instantiating an
    incomplete subclass fails (ABC enforcement — the jax-idiomatic analog of
    the reference's NotImplementedError checks)."""
    from metrics_tpu.core.metric import Metric

    class OnlyCompute(Metric):
        def _compute(self):
            return None

    class OnlyUpdate(Metric):
        def _update(self):
            pass

    with pytest.raises(TypeError, match="_update"):
        OnlyCompute()
    with pytest.raises(TypeError, match="_compute"):
        OnlyUpdate()


def test_forward_cache_reset():
    """reset() clears the forward cache (reference test_metric.py:330-337)."""
    m = DummyMetric()
    m(jnp.asarray(2.0))
    assert float(m._forward_cache) == 2.0
    m.reset()
    assert m._forward_cache is None


def test_persistent_flag_toggles_all_states():
    m = DummyMetric()
    assert m._persistent["x"] is False
    m.persistent(True)
    assert m._persistent["x"] is True
    # states are present in the checkpointable pytree regardless (state_dict
    # here is the orbax-compatible full pytree, not a torch buffer registry)
    assert "x" in m.state_dict()


def test_child_metric_state_dict_prefixing():
    """States of nested child metrics appear under a dotted prefix
    (reference test_metric.py:259-277 via nn.Module nesting)."""
    from metrics_tpu.wrappers import MinMaxMetric

    wrapped = MinMaxMetric(DummyMetric())
    wrapped.update(jnp.asarray(3.0))
    sd = wrapped.state_dict()
    assert any(k.endswith(".x") for k in sd), sd.keys()
    restored = MinMaxMetric(DummyMetric())
    restored.load_state_dict(sd)
    np.testing.assert_allclose(
        float(restored.compute()["raw"]), float(wrapped.compute()["raw"]), atol=1e-6
    )


def test_load_state_dict_pre_counter_checkpoint_uses_unweighted_merge():
    """Restoring an old (pre-counter) checkpoint must not leave _n_updates
    at 0: a 0 weights that side's accumulated mean to ZERO in merge_states,
    silently discarding its data (ADVICE round 5 medium). load_state_dict
    sets the sentinel -1, merges fall back to the unweighted mean, and the
    sentinel survives bumps, snapshots, and chained merges."""

    class MeanStateMetric(Metric):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.add_state("m", jnp.array(0.0), dist_reduce_fx="mean")

        def _update(self, x):
            self.m = jnp.asarray(x, dtype=jnp.float32)

        def _compute(self):
            return self.m

    # an old checkpoint: real state present, no _n_updates key
    old_ckpt = {"m": jnp.array(6.0)}
    restored = MeanStateMetric()
    restored.load_state_dict(old_ckpt)
    assert int(getattr(restored, "_n_updates")) == -1

    # its re-snapshot carries the sentinel, and merging with a counted side
    # (2 updates) gives the unweighted mean — NOT the 0-weighted 1.0 the
    # stale counter produced before the fix, and not (2*2+0*6)/2 either
    snap = restored.state_dict()
    assert int(snap["_n_updates"]) == -1
    counted = MeanStateMetric()
    s = counted.update_state(counted.init_state(), 2.0)
    s = counted.update_state(s, 2.0)
    merged = restored.merge_states(dict(snap), s)
    assert float(merged["m"]) == pytest.approx((6.0 + 2.0) / 2)
    assert int(merged["_n_updates"]) == -1  # uncertainty propagates

    # updates after the restore keep the sentinel (a rebuilt small count
    # would miss the restored history and be trusted as a wrong weight)
    restored.update(4.0)
    assert int(getattr(restored, "_n_updates")) == -1

    # counter PRESENT in the checkpoint: weighted merge still works
    good = MeanStateMetric()
    g = good.update_state(good.init_state(), 8.0)
    merged2 = good.merge_states(g, s)  # 1 update of 8.0 vs 2 updates of 2.0
    assert float(merged2["m"]) == pytest.approx((8.0 + 2 * 2.0) / 3)

    # a checkpoint with NO real states restored leaves the counter alone
    fresh = MeanStateMetric()
    fresh.load_state_dict({})
    assert int(getattr(fresh, "_n_updates")) == 0


def test_auto_counter_sentinel_survives_distributed_sum():
    """The -1 'history unknown' sentinel must survive cross-rank counter
    reductions — a plain sum would launder it into a confident positive
    count that merge_states then trusts as a weight. Covers both the
    host-level gather-reduce (_sync_dist) and the in-mesh callable-reducer
    path (sync_in_mesh via state_reductions)."""
    from metrics_tpu.core.metric import _sentinel_count_sum

    assert int(_sentinel_count_sum(jnp.asarray([3, 4], jnp.int32))) == 7
    assert int(_sentinel_count_sum(jnp.asarray([-1, 5], jnp.int32))) == -1

    class MeanStateMetric(Metric):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.add_state("m", jnp.array(0.0), dist_reduce_fx="mean")

        def _update(self, x):
            self.m = jnp.asarray(x, dtype=jnp.float32)

        def _compute(self):
            return self.m

    # host-level: this rank restored a pre-counter checkpoint (sentinel -1),
    # the simulated peer rank has 5 counted updates
    m = MeanStateMetric()
    m.load_state_dict({"m": jnp.asarray(6.0)})

    def fake_gather(x, group=None):
        if jnp.issubdtype(x.dtype, jnp.integer):
            return [x, jnp.asarray(5, jnp.int32)]
        return [x, jnp.asarray(2.0)]

    m.sync(dist_sync_fn=fake_gather)
    assert float(getattr(m, "m")) == pytest.approx(4.0)  # stack-then-mean
    assert int(getattr(m, "_n_updates")) == -1  # NOT 4
    m.unsync()

    # in-mesh: the counter's reducer rides sync_in_mesh's callable branch
    from metrics_tpu.utils.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.distributed import sync_in_mesh

    import numpy as _np

    mesh = Mesh(_np.array(jax.devices()[:2]), ("r",))
    counters = jnp.asarray([-1, 5], jnp.int32)
    means = jnp.asarray([6.0, 2.0], jnp.float32)

    def body(c, v):
        s = sync_in_mesh({"m": v[0], "_n_updates": c[0]}, m.state_reductions(), "r")
        return jnp.stack([s["m"], s["_n_updates"].astype(jnp.float32)])[None]

    out = jax.jit(
        shard_map(body, mesh=mesh, in_specs=(P("r"), P("r")), out_specs=P("r"))
    )(counters, means)
    _np.testing.assert_allclose(_np.asarray(out), [[4.0, -1.0], [4.0, -1.0]])
