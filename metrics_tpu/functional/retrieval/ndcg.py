"""Retrieval normalized discounted cumulative gain.

Behavior parity with /root/reference/torchmetrics/functional/retrieval/
ndcg.py:20-72 (sort + log2 discount; graded targets allowed).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs, _check_retrieval_k

Array = jax.Array


def _dcg(target: Array) -> Array:
    denom = jnp.log2(jnp.arange(target.shape[-1]) + 2.0)
    return jnp.sum(target / denom, axis=-1)


def retrieval_normalized_dcg(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """nDCG (at k) of a single query's ranking; targets may be graded.

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_normalized_dcg(jnp.array([.1, .2, .3, 4., 70.]), jnp.array([10, 0, 0, 1, 5]))
        Array(0.6956941, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target=True)
    k = preds.shape[-1] if k is None else k
    _check_retrieval_k(k)

    sorted_target = target[jnp.argsort(-preds, axis=-1)][:k]
    ideal_target = -jnp.sort(-target)[:k]

    ideal_dcg = _dcg(ideal_target)
    target_dcg = _dcg(sorted_target)

    return jnp.where(ideal_dcg == 0, 0.0, target_dcg / jnp.where(ideal_dcg == 0, 1.0, ideal_dcg))
