"""Retrieval precision.

Behavior parity with /root/reference/torchmetrics/functional/retrieval/
precision.py:20-58.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs, _check_retrieval_k

Array = jax.Array


def retrieval_precision(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Fraction of the top k retrieved documents that are relevant.

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_precision(jnp.array([0.2, 0.3, 0.5]), jnp.array([True, False, True]), k=2)
        Array(0.5, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if k is None:
        k = preds.shape[-1]
    _check_retrieval_k(k)

    if not jnp.sum(target):
        return jnp.asarray(0.0, dtype=preds.dtype)

    order = jnp.argsort(-preds, axis=-1)[: min(k, preds.shape[-1])]
    relevant = jnp.sum(target[order]).astype(jnp.float32)
    return relevant / k
