"""Wrapper tests: BootStrapper, ClasswiseWrapper, MinMaxMetric,
MultioutputWrapper, MetricTracker.

Mirrors /root/reference/tests/wrappers/ in spirit.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import (
    Accuracy,
    BootStrapper,
    ClasswiseWrapper,
    ExplainedVariance,
    MeanSquaredError,
    MetricCollection,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
    Precision,
    R2Score,
    Recall,
)
from metrics_tpu.wrappers.bootstrapping import _bootstrap_sampler

_rng = np.random.RandomState(42)


# ---------------------------------------------------------------------------
# BootStrapper
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("sampling_strategy", ["poisson", "multinomial"])
def test_bootstrap_sampler(sampling_strategy):
    idx = _bootstrap_sampler(50, sampling_strategy, np.random.RandomState(0))
    assert np.asarray(idx).min() >= 0 and np.asarray(idx).max() < 50


@pytest.mark.parametrize("sampling_strategy", ["poisson", "multinomial"])
def test_bootstrapper(sampling_strategy):
    base = MeanSquaredError()
    bs = BootStrapper(
        base, num_bootstraps=20, mean=True, std=True, quantile=0.95, raw=True,
        sampling_strategy=sampling_strategy, seed=0,
    )
    preds = jnp.asarray(_rng.rand(64), jnp.float32)
    target = jnp.asarray(_rng.rand(64), jnp.float32)
    bs.update(preds, target)
    out = bs.compute()
    assert set(out.keys()) == {"mean", "std", "quantile", "raw"}
    assert out["raw"].shape == (20,)
    true_mse = float(jnp.mean((preds - target) ** 2))
    assert abs(float(out["mean"]) - true_mse) < 0.05


def test_bootstrapper_invalid():
    with pytest.raises(ValueError):
        BootStrapper("not a metric")
    with pytest.raises(ValueError):
        BootStrapper(MeanSquaredError(), sampling_strategy="bad")


# ---------------------------------------------------------------------------
# ClasswiseWrapper
# ---------------------------------------------------------------------------


def test_classwise_wrapper():
    metric = ClasswiseWrapper(Accuracy(num_classes=3, average="none"), labels=["horse", "fish", "dog"])
    preds = jnp.asarray([0, 1, 2, 1])
    target = jnp.asarray([0, 1, 1, 1])
    out = metric(preds, target)
    assert set(out.keys()) == {"accuracy_horse", "accuracy_fish", "accuracy_dog"}
    metric.update(preds, target)
    out2 = metric.compute()
    assert float(out2["accuracy_horse"]) == 1.0

    nolabels = ClasswiseWrapper(Accuracy(num_classes=3, average="none"))
    out3 = nolabels(preds, target)
    assert set(out3.keys()) == {"accuracy_0", "accuracy_1", "accuracy_2"}

    with pytest.raises(ValueError):
        ClasswiseWrapper("nope")
    with pytest.raises(ValueError):
        ClasswiseWrapper(Accuracy(), labels=[1, 2])


def test_classwise_in_collection():
    mc = MetricCollection(
        {"acc": ClasswiseWrapper(Accuracy(num_classes=3, average="none"), labels=["a", "b", "c"])}
    )
    out = mc(jnp.asarray([0, 1, 2]), jnp.asarray([0, 1, 1]))
    assert set(out.keys()) == {"accuracy_a", "accuracy_b", "accuracy_c"}


# ---------------------------------------------------------------------------
# MinMaxMetric
# ---------------------------------------------------------------------------


def test_minmax_metric():
    mm = MinMaxMetric(Accuracy())
    labels = jnp.asarray([0, 1, 0, 1])
    out = mm(jnp.asarray([0, 1, 0, 1]), labels)  # acc 1.0
    assert float(out["raw"]) == 1.0 and float(out["min"]) == 1.0 and float(out["max"]) == 1.0
    mm.update(jnp.asarray([1, 0, 0, 1]), labels)  # acc drops
    out = mm.compute()
    assert float(out["min"]) < 1.0 and float(out["max"]) == 1.0
    mm.reset()
    assert float(mm.min_val) == float(jnp.inf)

    with pytest.raises(ValueError):
        MinMaxMetric("nope")


# ---------------------------------------------------------------------------
# MultioutputWrapper
# ---------------------------------------------------------------------------


def test_multioutput_r2():
    target = jnp.asarray([[0.5, 1.0], [-1.0, 1.0], [7.0, -6.0]])
    preds = jnp.asarray([[0.0, 2.0], [-1.0, 2.0], [8.0, -5.0]])
    r2 = MultioutputWrapper(R2Score(), 2)
    out = r2(preds, target)
    np.testing.assert_allclose(np.asarray(out[0]), 0.9654, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out[1]), 0.9082, atol=1e-4)


def test_multioutput_remove_nans():
    target = np.array([[0.5, 1.0], [-1.0, np.nan], [7.0, -6.0], [2.0, 1.5]], dtype=np.float32)
    preds = np.array([[0.0, 2.0], [-1.0, 2.0], [8.0, -5.0], [2.5, 1.0]], dtype=np.float32)
    r2 = MultioutputWrapper(R2Score(), 2)
    r2.update(jnp.asarray(preds), jnp.asarray(target))
    out = r2.compute()
    # second output computed on the 3 non-nan rows
    from sklearn.metrics import r2_score as sk_r2

    np.testing.assert_allclose(np.asarray(out[0]), sk_r2(target[:, 0], preds[:, 0]), atol=1e-4)
    keep = ~np.isnan(target[:, 1])
    np.testing.assert_allclose(np.asarray(out[1]), sk_r2(target[keep, 1], preds[keep, 1]), atol=1e-4)


# ---------------------------------------------------------------------------
# MetricTracker
# ---------------------------------------------------------------------------


def test_tracker_single_metric():
    tracker = MetricTracker(Accuracy(num_classes=10), maximize=True)
    accs = []
    rng = np.random.RandomState(0)
    for epoch in range(5):
        tracker.increment()
        preds = jnp.asarray(rng.randint(0, 10, 100))
        target = jnp.asarray(rng.randint(0, 10, 100))
        tracker.update(preds, target)
        accs.append(float(tracker.compute()))
    all_vals = np.asarray(tracker.compute_all())
    np.testing.assert_allclose(all_vals, accs, atol=1e-6)
    best, step = tracker.best_metric(return_step=True)
    assert best == max(accs)
    assert step == int(np.argmax(accs))
    assert tracker.n_steps == 5


def test_tracker_collection():
    tracker = MetricTracker(
        MetricCollection([MeanSquaredError(), ExplainedVariance()]), maximize=[False, True]
    )
    rng = np.random.RandomState(0)
    for epoch in range(3):
        tracker.increment()
        tracker.update(jnp.asarray(rng.randn(100), jnp.float32), jnp.asarray(rng.randn(100), jnp.float32))
    res = tracker.compute_all()
    assert set(res.keys()) == {"MeanSquaredError", "ExplainedVariance"}
    assert res["MeanSquaredError"].shape == (3,)
    best, steps = tracker.best_metric(return_step=True)
    assert set(best.keys()) == {"MeanSquaredError", "ExplainedVariance"}


def test_minmax_forward_accumulates():
    """forward() must not wipe the wrapped metric's accumulated state."""
    mm = MinMaxMetric(Accuracy())
    labels = jnp.asarray([0, 1, 0, 1])
    mm(jnp.asarray([0, 1, 0, 1]), labels)  # acc 1.0
    mm(jnp.asarray([1, 0, 1, 0]), labels)  # acc 0.0
    out = mm.compute()
    assert float(out["raw"]) == pytest.approx(0.5)  # accumulated over 8 samples
    assert float(out["max"]) == 1.0 and float(out["min"]) == 0.0


def test_bootstrapper_forward_accumulates():
    bs = BootStrapper(MeanSquaredError(), num_bootstraps=4, seed=0)
    preds = jnp.asarray(_rng.rand(32), jnp.float32)
    target = jnp.asarray(_rng.rand(32), jnp.float32)
    bs(preds, target)
    bs(preds + 1.0, target)  # second forward must add to, not replace, state
    out = bs.compute()
    assert float(out["mean"]) > float(jnp.mean((preds - target) ** 2))


def test_wrapper_state_dict_roundtrip():
    bs = BootStrapper(MeanSquaredError(), num_bootstraps=3, seed=0)
    preds = jnp.asarray(_rng.rand(16), jnp.float32)
    target = jnp.asarray(_rng.rand(16), jnp.float32)
    bs.update(preds, target)
    sd = bs.state_dict()
    assert sd, "BootStrapper state_dict must include bootstrap copies"
    bs2 = BootStrapper(MeanSquaredError(), num_bootstraps=3, seed=0)
    bs2.load_state_dict(sd)
    np.testing.assert_allclose(
        np.asarray(bs2.compute()["mean"]), np.asarray(bs.compute()["mean"]), atol=1e-6
    )

    mm = MinMaxMetric(Accuracy())
    mm.update(jnp.asarray([0, 1]), jnp.asarray([0, 1]))
    mm.compute()
    sd = mm.state_dict()
    assert "min_val" in sd and "max_val" in sd


def test_wrappers_not_merged_in_collection():
    """Compute-group discovery must not merge unrelated wrappers."""
    mc = MetricCollection(
        {
            "cls": ClasswiseWrapper(Accuracy(num_classes=3, average="none")),
            "minmax": MinMaxMetric(Precision(num_classes=3, average="macro")),
        }
    )
    p = jnp.asarray(_rng.randint(0, 3, 32))
    t = jnp.asarray(_rng.randint(0, 3, 32))
    mc.update(p, t)
    assert len(mc.compute_groups) == 2


def test_tracker_requires_increment():
    tracker = MetricTracker(Accuracy())
    with pytest.raises(ValueError, match="increment"):
        tracker.update(jnp.asarray([1]), jnp.asarray([1]))
    with pytest.raises(TypeError):
        MetricTracker("nope")
