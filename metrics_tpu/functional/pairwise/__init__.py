from metrics_tpu.functional.pairwise.cosine import pairwise_cosine_similarity  # noqa: F401
from metrics_tpu.functional.pairwise.euclidean import pairwise_euclidean_distance  # noqa: F401
from metrics_tpu.functional.pairwise.linear import pairwise_linear_similarity  # noqa: F401
from metrics_tpu.functional.pairwise.manhattan import pairwise_manhattan_distance  # noqa: F401
