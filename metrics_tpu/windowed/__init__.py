"""Windowed metric state: sliding-window and exponential-decay semantics
for any fusible metric.

:class:`WindowedMetric` turns an all-of-time metric into a live one — a
ring of ``R`` state copies (one per bucket of updates, rotated in-place by
``.at[slot].set`` inside the fused dispatch) or a per-leaf exponentially
decayed sum — while composing unchanged with ``compile_update`` /
``compile_update_async`` / ``sync_pytree_in_mesh`` and with
``SlicedMetric`` (``WindowedMetric(SlicedMetric(...))`` is the per-tenant
windowed surface). The reference-vs-live drift comparator in
:mod:`metrics_tpu.observability.drift` reads its window folds.
"""
from metrics_tpu.windowed.metric import (
    DECAY_WEIGHT,
    RING_COUNT,
    RING_ROWS,
    WindowedMetric,
)
from metrics_tpu.windowed.reducers import decay_sum_fx, ring_merge_fx, ring_sum_fx

__all__ = [
    "DECAY_WEIGHT",
    "RING_COUNT",
    "RING_ROWS",
    "WindowedMetric",
    "decay_sum_fx",
    "ring_merge_fx",
    "ring_sum_fx",
]
