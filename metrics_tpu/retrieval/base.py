"""RetrievalMetric base: grouped-by-query mean of a per-query metric.

Behavior parity with /root/reference/torchmetrics/retrieval/base.py:27-150:
compute = group by query id -> per-group ``_metric`` -> mean;
``empty_target_action`` in neg/pos/skip/error.

**Default state — the fixed-capacity per-query table**
(:mod:`metrics_tpu.retrieval.table`). ``update(preds, target, indexes)``
segment-scatters each document into its query's row of a packed
``[max_queries, 7 + 2*max_docs]`` leaf: exact per-query counters
(docs seen / positive mass / negative count) plus the stored document
slots, with a deterministic hash-key reservoir over query rows and a
fused top-k compaction over document slots past capacity. The update is a
pure fixed-shape ``jnp`` transform, so retrieval metrics fuse
(``MetricCollection.compile_update``), bucket ragged shapes (the
``n_valid`` pad-mask contract), ingest asynchronously, and sync across a
mesh in the fused collective round like any sketch-state metric. Inside
the lossless window — distinct queries ``<= max_queries`` and per-query
documents ``<= max_docs`` — results are bit-identical to the cat-state
path on integer-exact data; past it, metrics degrade to their
depth-truncated (top-k-pooled) variants while the empty-query policy
stays exact through the counters.

**`exact=True`** restores the reference's unbounded cat-state
(``indexes/preds/target`` lists) bit-for-bit — including the packed
``[num_queries, max_docs]`` device compute path (SURVEY §7.5) and the
host group-loop fallback for heavily skewed query sizes. Exact instances
flip instance-level ``__jit_unsafe__`` and stay on the eager path.

Subclasses declare their padded row kernel via ``_padded_metric``
(functional/retrieval/padded.py); both state modes share those kernels.
User subclasses that only implement ``_metric`` fall back to a host group
loop in either mode (exact-parity semantics, eager speed).
"""
import time
import weakref
from abc import ABC, abstractmethod
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from collections import OrderedDict

from metrics_tpu.core.readers import ReaderCache, pad_ids, round_up_bucket
from metrics_tpu.functional.retrieval.padded import (
    _padded_compute_fn,
    _padded_compute_fn_raw,
    pack_queries_cached,
    sorted_row_layout,
)
from metrics_tpu.retrieval.table import (
    retrieval_table_fill,
    retrieval_table_init,
    retrieval_table_insert,
    retrieval_table_layout,
    retrieval_table_layout_rows,
    retrieval_table_merge_fx,
)
from metrics_tpu.observability.memory import register_cache_plane
from metrics_tpu.observability.recorder import _DEFAULT_RECORDER as _TELEMETRY
from metrics_tpu.sketches.compat import register_exact_list_states, warn_exact_buffer
from metrics_tpu.utils.checks import (
    _check_retrieval_inputs,
    _check_retrieval_inputs_static,
    _is_concrete,
)
from metrics_tpu.utils.data import dim_zero_cat, get_group_indexes

Array = jax.Array

#: hard LRU bound on the layout memo: a serving process computes a handful
#: of retrieval metrics over one or two tables, so entries past this are
#: leaks, not reuse (asserted by the retrieval test suite)
_LAYOUT_CACHE_MAX = 8

#: (owner id, write epoch) -> (table-leaf id, unpacked padded layout,
#: weakref finalizer). The epoch key makes repeated reads of an unwritten
#: metric pure cache hits — the table's WRITE CLOCK, not the array object,
#: is what "unchanged" means (a device transfer or unsync can swap the
#: object without changing a bit). The stored leaf id still guards the
#: entry (an epoch hit with a different leaf recomputes) and feeds the
#: identity scan: a compute group's metrics share ONE qtable leaf by
#: reference, so a sibling's entry for the same leaf is aliased instead of
#: re-unpacked — and, because the aliased layout returns the SAME array
#: objects, the group shares one per-row sort through sorted_row_layout's
#: identity cache. Entries die with their leaf (weakref finalizers) or by
#: LRU eviction, whichever first.
_LAYOUT_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()

#: lifetime eviction totals for the layout memo (process-wide, like the
#: cache itself): count + bytes dropped, surfaced on the compute read event
#: next to ``cache_hit`` and by :func:`layout_cache_totals`
_LAYOUT_EVICTIONS = 0
_LAYOUT_EVICTED_BYTES = 0


def _layout_nbytes(layout: tuple) -> int:
    """Bytes held by one memoized layout tuple (its padded unpack arrays)."""
    return int(
        sum(getattr(leaf, "nbytes", 0) or 0 for leaf in jax.tree_util.tree_leaves(layout))
    )


def _layout_cache_nbytes() -> int:
    """Total bytes in the layout memo, deduped by layout identity — a
    compute-group sibling's entry ALIASES the same layout tuple (same array
    objects), so it must not count twice."""
    seen: set = set()
    total = 0
    for _tid, layout, _fin in _LAYOUT_CACHE.values():
        if id(layout) in seen:
            continue
        seen.add(id(layout))
        total += _layout_nbytes(layout)
    return total


def layout_cache_totals() -> dict:
    """The layout memo's current inventory and lifetime eviction totals:
    ``{"entries", "nbytes", "evictions", "evicted_bytes"}``."""
    return {
        "entries": len(_LAYOUT_CACHE),
        "nbytes": _layout_cache_nbytes(),
        "evictions": _LAYOUT_EVICTIONS,
        "evicted_bytes": _LAYOUT_EVICTED_BYTES,
    }


def _layout_cache_evict(key: tuple) -> None:
    global _LAYOUT_EVICTIONS, _LAYOUT_EVICTED_BYTES
    entry = _LAYOUT_CACHE.pop(key, None)
    if entry is None:
        return
    if entry[2] is not None:
        entry[2].detach()
    _LAYOUT_EVICTIONS += 1
    dropped = _layout_nbytes(entry[1])
    _LAYOUT_EVICTED_BYTES += dropped
    if _TELEMETRY.enabled:
        # runs from LRU overflow AND weakref finalizers (gc-time): the
        # recorder hook is lock-safe and allocation-light, but never let a
        # telemetry failure propagate out of a finalizer
        try:
            _TELEMETRY.record_cache_plane(
                "retrieval_layout",
                entries=len(_LAYOUT_CACHE),
                nbytes=_layout_cache_nbytes(),
                evictions=1,
                evicted_bytes=dropped,
            )
        except Exception:
            pass


def _layout_cache_store(key: tuple, qtable: Array, layout: tuple) -> None:
    try:
        fin = weakref.finalize(qtable, _layout_cache_evict, key)
    except TypeError:  # non-weakref-able leaf: serve uncached
        return
    _LAYOUT_CACHE[key] = (id(qtable), layout, fin)
    while len(_LAYOUT_CACHE) > _LAYOUT_CACHE_MAX:
        k0 = next(iter(_LAYOUT_CACHE))
        _layout_cache_evict(k0)


def _table_layout_cached(qtable: Array, epoch_key: Optional[tuple] = None):
    """``(layout, cache_hit)`` — the memoized padded unpack of ``qtable``.
    A hit means no unpack ran: either the owner's epoch key matched (same
    write clock, same leaf) or the identity scan found a sibling's entry
    for the same leaf object."""
    if isinstance(qtable, jax.core.Tracer):  # never cache traced values
        return retrieval_table_layout(qtable), False
    tid = id(qtable)
    if epoch_key is not None:
        hit = _LAYOUT_CACHE.get(epoch_key)
        if hit is not None and hit[0] == tid:
            _LAYOUT_CACHE.move_to_end(epoch_key)
            return hit[1], True
    # identity scan (bounded by _LAYOUT_CACHE_MAX): a compute-group sibling
    # may have unpacked this exact leaf under its own epoch key
    for k, (tid2, layout2, _) in _LAYOUT_CACHE.items():
        if tid2 == tid:
            _LAYOUT_CACHE.move_to_end(k)
            if epoch_key is not None:
                _layout_cache_store(epoch_key, qtable, layout2)
            return layout2, True
    layout = retrieval_table_layout(qtable)
    _layout_cache_store(epoch_key if epoch_key is not None else ("id", tid), qtable, layout)
    return layout, False


# process-wide memory plane for the layout memo (one cache, one plane)
register_cache_plane("retrieval_layout", _layout_cache_nbytes)


class RetrievalMetric(Metric, ABC):
    """Base class for retrieval metrics over (indexes, preds, target) triples."""

    higher_is_better = True
    __jit_unsafe__ = False  # table-state default: fixed-shape trace-safe update
    __exact_mode_attr__ = "_exact"
    #: bucketed fused dispatch threads ``n_valid`` so edge-pad rows are
    #: masked out of the table insert instead of needing a pad correction
    __fused_mask_valid__ = True

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        exact: bool = False,
        max_queries: int = 1024,
        max_docs: int = 128,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False

        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        self._exact = bool(exact)
        if self._exact:
            register_exact_list_states(self, ("indexes", "preds", "target"), dist_reduce_fx=None)
            warn_exact_buffer(type(self).__name__, "indexes, targets and predictions")
        else:
            self.max_queries = max_queries
            self.max_docs = max_docs
            self.add_state(
                "qtable",
                default=retrieval_table_init(max_queries, max_docs),
                dist_reduce_fx=retrieval_table_merge_fx(),
            )
        #: occupied rows unpacked by the last table compute (read telemetry only)
        self._last_table_rows = 0
        #: whether the last table compute reused a memoized layout
        self._last_layout_cache_hit = False
        #: pre-lowered subset-unpack executables (table-state reads only)
        self._readers = ReaderCache()

    def _update(
        self, preds: Array, target: Array, indexes: Array, n_valid: Optional[Array] = None
    ) -> None:
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")

        if self._exact:
            indexes, preds, target = _check_retrieval_inputs(
                indexes,
                preds,
                target,
                allow_non_binary_target=self.allow_non_binary_target,
                ignore_index=self.ignore_index,
            )
            self.indexes.append(indexes)
            self.preds.append(preds)
            self.target.append(target)
            return

        indexes, preds, target, valid = _check_retrieval_inputs_static(
            indexes,
            preds,
            target,
            allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        self.qtable = retrieval_table_insert(
            self.qtable, indexes, preds, target, valid=valid, n_valid=n_valid
        )

    #: padded per-query row kernel ``(preds, target, mask, k) -> value`` from
    #: functional/retrieval/padded.py; None falls back to the host group loop
    _padded_metric: Optional[Callable] = None
    #: static top-k forwarded to the padded kernel (subclasses with a ``k`` arg
    #: override via property)
    _padded_k: Optional[int] = None

    def _group_empty(self, mini_target: Array) -> bool:
        """True if this query has no positive target (override to invert)."""
        return not bool(jnp.sum(mini_target))

    def _empty_rows(self, padded_target: Array, mask: Array) -> Array:
        """Vectorized ``_group_empty`` over the padded layout (override to invert)."""
        return (padded_target * mask).sum(-1) == 0

    def _table_empty_rows(self, pos_mass: Array, neg_count: Array) -> Array:
        """``_empty_rows`` from the table's EXACT counters — never degraded
        by document truncation (override to invert, see FallOut)."""
        return pos_mass <= 0

    def _empty_error_message(self) -> str:
        return "`compute` method was provided with a query with no positive target."

    def _compute(self) -> Array:
        if not self._exact:
            return self._compute_table()
        if self._padded_metric is not None:
            return self._compute_padded()
        return self._compute_host_loop()

    def _read_extras(self) -> dict:
        # surfaced on the typed ``read`` event emitted by Metric.compute;
        # the layout-memo eviction totals ride alongside ``cache_hit`` so a
        # thrashing memo (evictions climbing while hits hold) is visible on
        # the same event stream that shows the hit rate
        return {
            "table_rows": self._last_table_rows,
            "cache_hit": self._last_layout_cache_hit,
            "layout_entries": len(_LAYOUT_CACHE),
            "layout_evictions": _LAYOUT_EVICTIONS,
            "layout_evicted_bytes": _LAYOUT_EVICTED_BYTES,
        }

    def table_rows_layout(self, rows: Any):
        """Subset unpack: the padded layout of just the given TABLE rows,
        in caller order (no cross-row qid sort) — what an incremental
        consumer that tracks its own row set reads instead of paying the
        full ``[max_queries, cap]`` unpack. Returns ``(padded_preds,
        padded_target, mask, row_valid, pos_mass, neg_count, n_seen,
        qid)``, each leading with ``len(rows)``.

        Concrete host row ids route through a pre-lowered subset reader
        keyed on the row-count bucket (one executable per bucket, padding
        by repeating the last row — re-reading a row is idempotent and the
        pad rows are sliced back off); traced ids fall through to the
        plain jnp unpack. Table-state mode only."""
        if self._exact:
            raise ValueError(
                "table_rows_layout() reads the fixed-capacity table state;"
                " exact=True metrics keep cat-state lists"
            )
        qtable = jnp.asarray(self.qtable)
        if not _is_concrete(qtable) or isinstance(rows, jax.core.Tracer):
            return retrieval_table_layout_rows(qtable, jnp.asarray(rows))
        rows = np.asarray(rows, np.int32).reshape(-1)
        if rows.size == 0:
            raise ValueError("table_rows_layout() needs at least one row id")
        n = rows.size
        bucket = round_up_bucket(n, self.max_queries)
        idx = jnp.asarray(pad_ids(rows, bucket))

        def build():
            return retrieval_table_layout_rows

        t0 = time.perf_counter() if _TELEMETRY.enabled else 0.0
        reader = self._readers.get("table_subset", build, qtable, idx, bucket=bucket)
        out = tuple(x[:n] for x in reader(qtable, idx))
        if _TELEMETRY.enabled:
            _TELEMETRY.record_read(
                "table",
                self,
                duration_s=time.perf_counter() - t0,
                table_rows=n,
                fanin=n,
            )
        return out

    # ------------------------------------------------------------------
    # table-state compute (the fixed-capacity default)
    # ------------------------------------------------------------------
    def _compute_table(self) -> Array:
        """Compute over the fixed-capacity table: rows unpack to the same
        padded layout the exact path's device pack produces (query-id
        order, so in-window results match bit-for-bit on integer-exact
        data), empty flags come from the exact counters, and unoccupied
        rows carry zero weight in the final mean."""
        qtable = self.qtable
        if _is_concrete(qtable) and int(retrieval_table_fill(qtable)) == 0:
            raise ValueError(
                "`indexes` is empty — the retrieval metric has no accumulated samples;"
                " call `update` before `compute`."
            )
        # key the unpack on this metric's write epoch: repeated reads of an
        # unwritten table are cache hits regardless of leaf identity; a
        # synced (cross-rank) leaf has no local epoch, so it rides the
        # identity scan only
        epoch_key = None if self._is_synced else (id(self), self._write_epoch)
        layout, self._last_layout_cache_hit = _table_layout_cached(qtable, epoch_key)
        padded_preds, padded_target, mask, row_valid, pos_mass, neg_count, _ = layout
        if _TELEMETRY.enabled and _is_concrete(row_valid):
            self._last_table_rows = int(jnp.sum(row_valid))
        empty = self._table_empty_rows(pos_mass, neg_count)
        if self.empty_target_action == "error" and _is_concrete(qtable):
            if bool(jnp.any(empty & row_valid)):
                raise ValueError(self._empty_error_message())

        kernel = type(self)._padded_metric
        if kernel is None:
            # user subclasses without a padded kernel: host loop over the
            # occupied rows (exact-parity semantics, eager speed)
            return self._compute_table_host_loop(
                padded_preds, padded_target, mask, row_valid, empty
            )
        weights = row_valid.astype(jnp.float32)
        sorted_fn = getattr(kernel, "sorted_fn", None)
        if sorted_fn is not None:
            st, sm = sorted_row_layout(padded_preds, padded_target, mask)
            run = _padded_compute_fn(
                kernel, self._padded_k, self.empty_target_action, weighted=True
            )
            return run(st, sm, padded_target, jnp.asarray(empty), weights)
        run = _padded_compute_fn_raw(
            kernel, self._padded_k, self.empty_target_action, weighted=True
        )
        return run(padded_preds, padded_target, mask, jnp.asarray(empty), weights)

    def _compute_table_host_loop(
        self, padded_preds: Array, padded_target: Array, mask: Array, row_valid: Array, empty: Array
    ) -> Array:
        res = []
        fills = np.asarray(jnp.sum(mask, axis=-1))
        rv = np.asarray(row_valid)
        emp = np.asarray(empty)
        for r in range(padded_preds.shape[0]):
            if not rv[r]:
                continue
            if emp[r]:
                if self.empty_target_action == "error":
                    raise ValueError(self._empty_error_message())
                if self.empty_target_action == "pos":
                    res.append(jnp.asarray(1.0))
                elif self.empty_target_action == "neg":
                    res.append(jnp.asarray(0.0))
            else:
                n = int(fills[r])
                res.append(self._metric(padded_preds[r, :n], padded_target[r, :n]))
        if res:
            return jnp.mean(jnp.stack([jnp.asarray(x, jnp.float32) for x in res]))
        return jnp.asarray(0.0, jnp.float32)

    # ------------------------------------------------------------------
    # exact-mode (cat-state) compute paths
    # ------------------------------------------------------------------
    def _compute_padded(self) -> Array:
        """Device-resident compute over the packed [num_queries, max_docs]
        layout: pack (sort + scatter), per-query kernels, empty policy, and
        mean all run on device; only two static-shape scalars (and the error
        flag when ``empty_target_action='error'``) cross to the host.

        The pack is memoized on the identity of the state arrays
        (``pack_queries_cached``): metrics sharing states through a
        MetricCollection compute group — e.g. NDCG + MAP over one query
        stream — pack once and each run only their own row kernel.
        """
        as_list = lambda s: s if isinstance(s, list) else [s]
        # heavily skewed query sizes make the [Q, Dmax] padding blow up (one
        # 50k-doc query among 100k small ones -> ~billions of padded slots);
        # past 16x expansion over the raw data the O(N) host loop wins
        packed = pack_queries_cached(
            as_list(self.indexes), as_list(self.preds), as_list(self.target), max_expand=16
        )
        if packed is None:
            return self._compute_host_loop()
        padded_preds, padded_target, mask = packed
        empty = self._empty_rows(padded_target, mask)
        if self.empty_target_action == "error" and bool(jnp.any(empty)):
            raise ValueError(self._empty_error_message())

        kernel = type(self)._padded_metric
        sorted_fn = getattr(kernel, "sorted_fn", None)
        if sorted_fn is not None:
            # shared-sort path: the per-row argsort is memoized per pack, so
            # every metric over this pack (a compute-group collection) sorts
            # once and runs only its own sorted kernel; NDCG's ideal ranking
            # is derived inside its compute jit from the raw target (the
            # other kernels' jits never touch that input)
            st, sm = sorted_row_layout(padded_preds, padded_target, mask)
            run = _padded_compute_fn(kernel, self._padded_k, self.empty_target_action)
            return run(st, sm, padded_target, jnp.asarray(empty))
        # user-supplied padded kernels without a sorted variant
        run = _padded_compute_fn_raw(kernel, self._padded_k, self.empty_target_action)
        return run(padded_preds, padded_target, mask, jnp.asarray(empty))

    def _compute_host_loop(self) -> Array:
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)

        res = []
        groups = get_group_indexes(indexes)

        for group in groups:
            mini_preds = preds[group]
            mini_target = target[group]

            if self._group_empty(mini_target):
                if self.empty_target_action == "error":
                    raise ValueError(self._empty_error_message())
                if self.empty_target_action == "pos":
                    res.append(jnp.asarray(1.0))
                elif self.empty_target_action == "neg":
                    res.append(jnp.asarray(0.0))
            else:
                res.append(self._metric(mini_preds, mini_target))

        if res:
            return jnp.mean(jnp.stack([jnp.asarray(x, dtype=preds.dtype) for x in res]))
        return jnp.asarray(0.0, dtype=preds.dtype)

    @abstractmethod
    def _metric(self, preds: Array, target: Array) -> Array:
        """Compute the metric for a single query's documents."""
