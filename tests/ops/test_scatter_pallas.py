"""Fused bincount / segment-scatter kernel vs the jnp path.

Interpret mode runs the REAL kernel body on CPU (the ``tests/ops/``
convention from test_box_iou_pallas.py). Integer-valued data makes every
f32 partial sum exact, so those cases pin BIT-identical agreement; the
composition cases drive the kernels through the same entry points the
metrics use (``_bincount``, ``SlicedMetric._update``, the fused collection
dispatch)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import ops
from metrics_tpu.ops.scatter_pallas import segment_sum_tiled
from metrics_tpu.utils.data import _bincount


@pytest.mark.parametrize(
    "b,d,s",
    [(1, 1, 1), (300, 3, 40), (512, 1, 128), (1024, 130, 7), (2048, 5, 1000)],
)
def test_segment_sum_interpret_bit_identical(b, d, s):
    """Ragged/padded tails included: B, D, S all off the tile multiples."""
    rng = np.random.default_rng(b * 31 + d * 7 + s)
    vals = jnp.asarray(rng.integers(-9, 9, (b, d)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, s, b), jnp.int32)
    got = segment_sum_tiled(vals, ids, s, interpret=True)
    want = jax.ops.segment_sum(vals, ids, num_segments=s)
    assert got.shape == (s, d)
    assert jnp.array_equal(got, want)


def test_segment_sum_1d_vals_keep_rank():
    vals = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    ids = jnp.asarray([0, 1, 0, 2], jnp.int32)
    got = segment_sum_tiled(vals, ids, 3, interpret=True)
    assert got.shape == (3,)
    assert jnp.array_equal(got, jnp.asarray([4.0, 2.0, 4.0]))


def test_segment_sum_drops_negative_and_oob_ids():
    """jax.ops.segment_sum's documented semantics: ids outside
    [0, num_segments) contribute nothing — on BOTH backends."""
    vals = jnp.ones((6,), jnp.float32)
    ids = jnp.asarray([-3, -1, 0, 1, 4, 99], jnp.int32)
    want = jax.ops.segment_sum(vals, ids, num_segments=4)
    got = segment_sum_tiled(vals, ids, 4, interpret=True)
    assert jnp.array_equal(got, want)
    assert jnp.array_equal(got, jnp.asarray([1.0, 1.0, 0.0, 0.0]))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.int32, jnp.bfloat16, jnp.int8])
def test_segment_sum_dispatch_preserves_dtype(dtype):
    """Small-integer data: exact in every listed dtype's f32 image, so the
    cast-back matches the fallback bit for bit."""
    rng = np.random.default_rng(3)
    vals = jnp.asarray(rng.integers(0, 4, (400, 2)), dtype)
    ids = jnp.asarray(rng.integers(0, 25, 400), jnp.int32)
    with ops.forced_backend("interpret"):
        got = ops.segment_sum_dispatch(vals, ids, 25)
    want = jax.ops.segment_sum(vals, ids, num_segments=25)
    assert got.dtype == want.dtype == jnp.dtype(dtype)
    assert jnp.array_equal(got, want)


def test_segment_sum_dispatch_flattens_trailing_dims():
    rng = np.random.default_rng(5)
    vals = jnp.asarray(rng.integers(0, 4, (128, 3, 5)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, 11, 128), jnp.int32)
    with ops.forced_backend("interpret"):
        got = ops.segment_sum_dispatch(vals, ids, 11)
    want = jax.ops.segment_sum(vals, ids, num_segments=11)
    assert got.shape == (11, 3, 5)
    assert jnp.array_equal(got, want)


# ---------------------------------------------------------------------------
# bincount: hardening + parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,length", [(1, 1), (700, 13), (4096, 1000), (5000, 10000)])
def test_bincount_interpret_bit_identical(n, length):
    rng = np.random.default_rng(n + length)
    x = jnp.asarray(rng.integers(0, length, n), jnp.int32)
    want = jnp.bincount(x, length=length)
    with ops.forced_backend("interpret"):
        got = ops.bincount_dispatch(x, length)
    assert got.dtype == want.dtype
    assert jnp.array_equal(got, want)


def test_bincount_positive_path_via_data_helper():
    x = jnp.asarray([0, 2, 2, 5, 1], jnp.int32)
    assert jnp.array_equal(_bincount(x, minlength=6), jnp.asarray([1, 1, 2, 0, 0, 1]))


def test_bincount_negative_host_values_raise():
    """Host-resident indices (numpy / Python sequences) are validated for
    free — no device round-trip."""
    with pytest.raises(ValueError, match="non-negative"):
        _bincount(np.asarray([0, -1, 2], np.int32), minlength=3)
    with pytest.raises(ValueError, match="non-negative"):
        ops.bincount_dispatch([0, -1, 2], 3)


def test_bincount_narrow_dtype_sentinel_cannot_wrap():
    """int8/int16 indices promote to int32 before the drop mask: in int8
    the minlength sentinel (e.g. 300) would wrap to 44 — a VALID bin —
    silently re-crediting the masked negatives."""

    @jax.jit  # traced: the drop-mask path
    def f(x):
        return ops.bincount_dispatch(x, 300)

    got = f(jnp.asarray([-1, -1, 0, 44], jnp.int8))
    want = jnp.zeros(300, got.dtype).at[0].set(1).at[44].set(1)
    assert jnp.array_equal(got, want)


def test_bincount_device_negatives_drop_without_sync():
    """Device arrays are NOT pulled back to host for validation (that
    blocking sync would serialize every eager classification update);
    negatives deterministically DROP instead — same fate as too-large
    ids, never raw scatter's silent bin-0 clip."""
    got = ops.bincount_dispatch(jnp.asarray([0, -1, 2], jnp.int32), 3)
    assert jnp.array_equal(got, jnp.asarray([1, 0, 1]))


def test_bincount_float_dtype_raises():
    with pytest.raises(TypeError, match="integer-typed"):
        ops.bincount_dispatch(jnp.asarray([0.5, 1.0]), 3)


@pytest.mark.parametrize("bad", [0, -1, 2.0, None, True])
def test_bincount_minlength_validated(bad):
    with pytest.raises(ValueError, match="minlength"):
        ops.bincount_dispatch(jnp.asarray([0, 1], jnp.int32), bad)


def test_bincount_traced_negatives_drop_not_clip():
    """Under a trace values cannot be inspected; negatives must be DROPPED
    (the fate of too-large ids), never silently clipped into bin 0 the way
    raw XLA scatter would credit them."""

    @jax.jit
    def f(x):
        return ops.bincount_dispatch(x, 3)

    got = f(jnp.asarray([-1, -7, 0, 2], jnp.int32))
    assert jnp.array_equal(got, jnp.asarray([1, 0, 1]))
    # raw jnp.bincount clips the two negatives into bin 0 — the hazard
    raw = jnp.bincount(jnp.asarray([-1, -7, 0, 2], jnp.int32), length=3)
    assert jnp.array_equal(raw, jnp.asarray([3, 0, 1]))


def test_bincount_traced_negatives_drop_in_interpret_too():
    @jax.jit
    def f(x):
        return ops.bincount_dispatch(x, 3)

    with ops.forced_backend("interpret"):
        got = f(jnp.asarray([-1, -7, 0, 2], jnp.int32))
    assert jnp.array_equal(got, jnp.asarray([1, 0, 1]))


# ---------------------------------------------------------------------------
# composition: the metric entry points that ride the dispatched ops
# ---------------------------------------------------------------------------


def test_confusion_matrix_through_interpret_kernel():
    from metrics_tpu.functional.classification.confusion_matrix import confusion_matrix

    rng = np.random.default_rng(11)
    preds = jnp.asarray(rng.integers(0, 7, 500), jnp.int32)
    target = jnp.asarray(rng.integers(0, 7, 500), jnp.int32)
    want = confusion_matrix(preds, target, num_classes=7)
    with ops.forced_backend("interpret"):
        got = confusion_matrix(preds, target, num_classes=7)
    assert jnp.array_equal(got, want)


def test_sliced_scatter_through_interpret_kernel():
    """SlicedMetric's per-leaf scatter (sum leaves + the row counter)
    through the real kernel body: integer-valued data, states bit-equal."""
    from metrics_tpu.regression import MeanSquaredError
    from metrics_tpu.sliced import SlicedMetric

    rng = np.random.default_rng(13)
    ids = jnp.asarray(rng.integers(0, 50, 600), jnp.int32)
    preds = jnp.asarray(rng.integers(0, 6, 600).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 6, 600).astype(np.float32))

    plain = SlicedMetric(MeanSquaredError(), num_slices=50)
    plain.update(ids, preds, target)
    forced = SlicedMetric(MeanSquaredError(), num_slices=50)
    with ops.forced_backend("interpret"):
        forced.update(ids, preds, target)
    for leaf in ("sum_squared_error", "total", "_slice_rows"):
        assert jnp.array_equal(getattr(plain, leaf), getattr(forced, leaf)), leaf


def test_fused_sliced_composition_matches_eager():
    """The dispatched ops inside a compiled fused collection (tracers at
    the dispatch boundary: the concrete-value validation must skip, the
    routing must resolve at trace time) — final states equal the eager
    per-metric path."""
    from metrics_tpu import MetricCollection
    from metrics_tpu.classification import ConfusionMatrix
    from metrics_tpu.regression import MeanSquaredError
    from metrics_tpu.sliced import SlicedMetric

    rng = np.random.default_rng(17)
    batches = [
        (
            jnp.asarray(rng.integers(0, 10, 256), jnp.int32),
            jnp.asarray(rng.integers(0, 4, 256), jnp.int32),
        )
        for _ in range(3)
    ]

    fused = MetricCollection({"cm": ConfusionMatrix(num_classes=4)})
    fused.compile_update()
    eager = ConfusionMatrix(num_classes=4)
    for ids, labels in batches:
        fused.update(labels, labels)
        eager.update(labels, labels)
    assert jnp.array_equal(fused["cm"].confmat, eager.confmat)

    sliced = SlicedMetric(MeanSquaredError(), num_slices=10)
    ref = [MeanSquaredError() for _ in range(10)]
    for ids, labels in batches:
        vals = labels.astype(jnp.float32)
        sliced.update(ids, vals, vals * 0)
        ids_np = np.asarray(ids)
        for i in np.unique(ids_np):
            m = ids_np == i
            ref[int(i)].update(vals[m], (vals * 0)[m])
    stacked = jnp.stack([jnp.asarray(r.sum_squared_error) for r in ref])
    assert jnp.array_equal(sliced.sum_squared_error, stacked)
